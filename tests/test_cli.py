"""CLI shell tests: scripted statements, backslash commands, print formats,
remote mode against a standalone cluster.

ref ballista-cli/src/{main,exec,command}.rs + print_format.rs.
"""

import io
import json
import subprocess
import sys

from tests.conftest import CPU_MESH_ENV


def _run_local(stdin: str, *extra_args: str) -> str:
    proc = subprocess.run(
        [sys.executable, "-m", "ballista_tpu.cli", *extra_args],
        input=stdin,
        env=CPU_MESH_ENV,
        capture_output=True,
        text=True,
        timeout=300,
    )
    assert proc.returncode == 0, f"stdout:\n{proc.stdout}\nstderr:\n{proc.stderr}"
    return proc.stdout


def test_cli_script_local(tmp_path):
    csv = tmp_path / "t.csv"
    csv.write_text("a,b\n1,x\n2,y\n3,x\n")
    out = _run_local(
        f"create external table t (a bigint, b varchar) "
        f"stored as csv with header row location '{csv}';\n"
        "select b, count(*) as n from t group by b order by b;\n"
        "\\d\n"
        "\\d t\n"
        "\\h\n"
        "\\h substr\n"
        "\\q\n"
    )
    assert "x" in out and "y" in out
    assert "table_name" in out  # \d -> show tables
    assert "column_name" in out  # \d t -> show columns
    assert "substr" in out  # \h
    assert "row(s) in set" in out


def test_cli_print_formats(tmp_path):
    csv = tmp_path / "t.csv"
    csv.write_text("a\n1\n2\n")
    base = (
        f"create external table t (a bigint) "
        f"stored as csv with header row location '{csv}';\n"
    )
    out = _run_local(
        base + "\\pset format csv\nselect * from t order by a;\n", "-q"
    )
    assert "a\n1\n2" in out.replace('"', "")
    out = _run_local(
        base + "\\pset format json\nselect * from t order by a;\n", "-q"
    )
    rows = json.loads(
        [l for l in out.splitlines() if l.startswith("[")][0]
    )
    assert rows == [{"a": 1}, {"a": 2}]
    out = _run_local(
        base + "\\pset format ndjson\nselect * from t order by a;\n", "-q"
    )
    nd = [json.loads(l) for l in out.splitlines() if l.startswith("{")]
    assert nd == [{"a": 1}, {"a": 2}]


def test_cli_file_mode(tmp_path):
    csv = tmp_path / "t.csv"
    csv.write_text("a\n5\n7\n")
    script = tmp_path / "q.sql"
    script.write_text(
        f"create external table t (a bigint) "
        f"stored as csv with header row location '{csv}';\n"
        "select sum(a) as s from t;\n"
    )
    out = _run_local("", "-f", str(script))
    assert "12" in out


def test_cli_quiet_and_errors(tmp_path):
    out = _run_local("select * from nosuch;\n\\q\n", "-q")
    assert "error:" in out
    assert "row(s) in set" not in out


def test_cli_multiline_statement(tmp_path):
    csv = tmp_path / "t.csv"
    csv.write_text("a\n1\n2\n3\n")
    out = _run_local(
        f"create external table t (a bigint) "
        f"stored as csv with header row location '{csv}';\n"
        "select\n  sum(a) as s\nfrom t\nwhere a > 1;\n",
        "-q",
    )
    assert "5" in out


def test_format_batch_unit():
    import pyarrow as pa

    from ballista_tpu.cli import format_batch

    t = pa.table({"x": pa.array([1, 2]), "s": pa.array(["a", "b"])})
    assert "x" in format_batch(t, "table")
    assert format_batch(t, "csv").splitlines()[0].replace('"', "") == "x,s"
    assert format_batch(t, "tsv").splitlines()[1].split("\t")[0] == "1"
    assert json.loads(format_batch(t, "json"))[1]["s"] == "b"
    empty = pa.table({"x": pa.array([], type=pa.int64())})
    assert format_batch(empty, "table") == "(empty)"
