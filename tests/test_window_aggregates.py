"""Aggregate window functions + frames and LAG/LEAD.

Oracle: pandas groupby rolling/expanding/shift. ref wire shape:
WindowAggExecNode (ballista.proto:531) with PhysicalWindowExprNode +
WindowFrame (ballista.proto:352-366, datafusion.proto:236-277); this
engine computes frames by prefix-sum differences on sorted rows
(exec/window.py).
"""

import numpy as np
import pandas as pd
import pyarrow as pa
import pytest

from ballista_tpu.errors import PlanError, SqlError
from ballista_tpu.exec.context import TpuContext


@pytest.fixture(scope="module")
def setup():
    r = np.random.default_rng(7)
    n = 2000
    t = pa.table(
        {
            "g": pa.array(r.integers(0, 15, n).astype(np.int64)),
            "o": pa.array(r.permutation(n).astype(np.int64)),
            "v": pa.array(np.round(r.uniform(0, 100, n), 6)),
            "q": pa.array(r.integers(1, 10, n).astype(np.int64)),
        }
    )
    ctx = TpuContext()
    ctx.register_table("t", t)
    df = t.to_pandas()
    return ctx, df


def _run(ctx, sql):
    return (
        ctx.sql(sql).collect().to_pandas().sort_values("o").reset_index(
            drop=True
        )
    )


def test_running_sum_default_frame(setup):
    ctx, df = setup
    got = _run(
        ctx,
        "select o, sum(v) over (partition by g order by o) as s from t",
    )
    want = df.sort_values(["g", "o"]).copy()
    want["s"] = want.groupby("g").v.cumsum()
    want = want.sort_values("o").reset_index(drop=True)
    np.testing.assert_allclose(got.s.to_numpy(), want.s.to_numpy(), rtol=1e-9)


def test_whole_partition_aggregates(setup):
    ctx, df = setup
    got = _run(
        ctx,
        "select o, sum(v) over (partition by g) as s, "
        "avg(v) over (partition by g) as a, "
        "count(*) over (partition by g) as c, "
        "min(v) over (partition by g) as mn, "
        "max(v) over (partition by g) as mx from t",
    )
    grp = df.groupby("g").v
    want = df.copy()
    want["s"] = grp.transform("sum")
    want["a"] = grp.transform("mean")
    want["c"] = grp.transform("count")
    want["mn"] = grp.transform("min")
    want["mx"] = grp.transform("max")
    want = want.sort_values("o").reset_index(drop=True)
    for c in ("s", "a", "mn", "mx"):
        np.testing.assert_allclose(
            got[c].to_numpy(), want[c].to_numpy(), rtol=1e-9, err_msg=c
        )
    assert got.c.tolist() == want.c.tolist()


def test_moving_average_rows_frame(setup):
    ctx, df = setup
    got = _run(
        ctx,
        "select o, avg(v) over (partition by g order by o "
        "rows between 2 preceding and current row) as ma, "
        "sum(q) over (partition by g order by o "
        "rows between 1 preceding and 1 following) as sq from t",
    )
    s = df.sort_values(["g", "o"]).copy()
    s["ma"] = (
        s.groupby("g").v.rolling(3, min_periods=1).mean().reset_index(
            level=0, drop=True
        )
    )
    s["sq"] = (
        s.groupby("g").q.rolling(3, min_periods=1, center=True)
        .sum()
        .reset_index(level=0, drop=True)
    )
    want = s.sort_values("o").reset_index(drop=True)
    np.testing.assert_allclose(got.ma.to_numpy(), want.ma.to_numpy(), rtol=1e-9)
    np.testing.assert_allclose(got.sq.to_numpy(), want.sq.to_numpy(), rtol=1e-9)


def test_running_min_max(setup):
    ctx, df = setup
    got = _run(
        ctx,
        "select o, min(v) over (partition by g order by o) as mn, "
        "max(v) over (partition by g order by o "
        "rows unbounded preceding) as mx from t",
    )
    s = df.sort_values(["g", "o"]).copy()
    s["mn"] = s.groupby("g").v.cummin()
    s["mx"] = s.groupby("g").v.cummax()
    want = s.sort_values("o").reset_index(drop=True)
    np.testing.assert_allclose(got.mn.to_numpy(), want.mn.to_numpy(), rtol=1e-9)
    np.testing.assert_allclose(got.mx.to_numpy(), want.mx.to_numpy(), rtol=1e-9)


def test_lag_lead(setup):
    ctx, df = setup
    got = _run(
        ctx,
        "select o, lag(v) over (partition by g order by o) as l1, "
        "lead(v, 2) over (partition by g order by o) as l2 from t",
    )
    s = df.sort_values(["g", "o"]).copy()
    s["l1"] = s.groupby("g").v.shift(1)
    s["l2"] = s.groupby("g").v.shift(-2)
    want = s.sort_values("o").reset_index(drop=True)
    for c in ("l1", "l2"):
        g = got[c].to_numpy()
        w = want[c].to_numpy()
        assert np.array_equal(np.isnan(g), np.isnan(w)), c
        np.testing.assert_allclose(
            g[~np.isnan(g)], w[~np.isnan(w)], rtol=1e-9, err_msg=c
        )


def test_rows_following_only_frame(setup):
    ctx, df = setup
    got = _run(
        ctx,
        "select o, sum(v) over (partition by g order by o "
        "rows between 1 following and 2 following) as s from t",
    )
    s = df.sort_values(["g", "o"]).copy()

    def f(grp):
        v = grp.to_numpy()
        out = np.full(len(v), np.nan)
        for i in range(len(v)):
            w = v[i + 1 : i + 3]
            if len(w):
                out[i] = w.sum()
        return pd.Series(out, index=grp.index)

    s["s"] = s.groupby("g").v.apply(f).reset_index(level=0, drop=True)
    want = s.sort_values("o").reset_index(drop=True)
    g = got.s.to_numpy()
    w = want.s.to_numpy()
    assert np.array_equal(np.isnan(g), np.isnan(w))
    np.testing.assert_allclose(g[~np.isnan(g)], w[~np.isnan(w)], rtol=1e-9)


def test_range_frame_peers(setup):
    ctx, df = setup
    # duplicate order values -> peer groups share the running value
    got = _run(
        ctx,
        "select o, sum(v) over (partition by g order by q) as s from t",
    )
    s = df.sort_values(["g", "q"], kind="stable").copy()
    # RANGE up..current: every peer (equal q) gets the peer-group total
    s["s"] = s.groupby("g").v.cumsum()
    peer_tot = s.groupby(["g", "q"]).s.transform("max")
    s["s"] = peer_tot
    want = s.sort_values("o").reset_index(drop=True)
    np.testing.assert_allclose(got.s.to_numpy(), want.s.to_numpy(), rtol=1e-9)


def test_window_with_nulls(setup):
    ctx, _ = setup
    t = pa.table(
        {
            "g": pa.array([0, 0, 0, 1, 1], type=pa.int64()),
            "o": pa.array([0, 1, 2, 3, 4], type=pa.int64()),
            "v": pa.array([1.0, None, 3.0, None, None]),
        }
    )
    ctx.register_table("tn", t)
    got = (
        ctx.sql(
            "select o, sum(v) over (partition by g order by o) as s, "
            "count(v) over (partition by g order by o) as c from tn"
        )
        .collect()
        .to_pandas()
        .sort_values("o")
    )
    # NULL v rows don't contribute; all-NULL partition -> NULL sum, count 0
    np.testing.assert_allclose(
        got.s.to_numpy()[:3], [1.0, 1.0, 4.0], rtol=1e-12
    )
    assert np.isnan(got.s.to_numpy()[3:]).all()
    assert got.c.tolist() == [1, 1, 2, 0, 0]
    ctx.deregister_table("tn")


def test_frame_errors(setup):
    ctx, _ = setup
    with pytest.raises(PlanError):
        ctx.sql(
            "select min(v) over (partition by g order by o "
            "rows between 2 preceding and current row) as m from t"
        ).collect()
    with pytest.raises((PlanError, SqlError)):
        ctx.sql(
            "select sum(v) over (order by o "
            "range between 2 preceding and current row) as m from t"
        ).collect()


def test_serde_roundtrip_window_aggregates(setup):
    ctx, _ = setup
    from ballista_tpu.serde import logical_from_proto, logical_to_proto

    logical = ctx.sql_to_logical(
        "select o, sum(v) over (partition by g order by o "
        "rows between 3 preceding and 1 following) as s, "
        "lag(v, 2) over (partition by g order by o) as l from t"
    )
    rt = logical_from_proto(logical_to_proto(logical))
    assert rt.display() == logical.display()


def test_min_empty_frame_is_null(setup):
    ctx, _ = setup
    t = pa.table(
        {
            "o": pa.array([0, 1, 2], type=pa.int64()),
            "v": pa.array([5.0, 3.0, 9.0]),
        }
    )
    ctx.register_table("tm", t)
    got = (
        ctx.sql(
            "select o, min(v) over (order by o rows between unbounded "
            "preceding and 1 preceding) as m from tm"
        )
        .collect()
        .to_pandas()
        .sort_values("o")
    )
    m = got.m.to_numpy()
    assert np.isnan(m[0])  # empty frame for the first row
    np.testing.assert_allclose(m[1:], [5.0, 3.0])
    ctx.deregister_table("tm")


def test_frame_start_after_end_rejected(setup):
    ctx, _ = setup
    for frame in (
        "rows between current row and 1 preceding",
        "rows between 1 preceding and 3 preceding",
        "rows between 3 following and 1 following",
    ):
        with pytest.raises(PlanError):
            ctx.sql(
                f"select sum(v) over (order by o {frame}) as s from t"
            ).collect()
