"""racelint: lock-discipline + state-machine static analysis (ISSUE 4).

Tier-1 contract: the analyzer runs CLEAN over the concurrent control
plane (scheduler/, executor/, client/flight.py, event_loop.py,
standalone.py, testing/faults.py) within the suppression budget, every
rule family both accepts a clean exemplar and rejects a seeded mutation,
the lock-order graph is acyclic and exported, the canonical state-machine
tables govern the runtime validator, and the combined
``python -m ballista_tpu.analysis`` gate aggregates all four analyzers
into one exit code."""

import textwrap
import threading

import pytest

from ballista_tpu.analysis import racelint, witness
from ballista_tpu.analysis.statemachine import (
    JOB_TRANSITIONS,
    STAGE_TRANSITIONS,
    TASK_TRANSITIONS,
    render_tables,
)

_HEADER = "import threading\nimport time\n"


def _lint(body: str):
    return racelint.lint_source(_HEADER + textwrap.dedent(body), "synth.py")


# ------------------------------------------------------------ tier-1 gate --


def test_control_plane_lints_clean():
    """The shipped control plane has zero racelint findings (tier-1)."""
    diags = racelint.lint_paths()
    assert diags == [], "\n".join(str(d) for d in diags)


# (the per-analyzer suppression-budget assertion moved to the single
# shared ledger test: tests/test_budget.py over analysis/budget.py)


def test_rule_catalog_documented():
    assert set(racelint.RULES) == {
        "unguarded-field", "lock-order-cycle", "blocking-under-lock",
        "undeclared-transition",
    }
    assert all(len(v) > 20 for v in racelint.RULES.values())


def test_lock_order_graph_exported_and_acyclic():
    edges = racelint.lock_order_graph()
    # the known inter-class orders of the control plane
    assert ("SchedulerServer._lock", "StageManager._lock") in edges
    assert ("SchedulerServer._lock", "ExecutorManager._lock") in edges
    # no reverse edges (acyclicity is also what rule 2 enforces)
    for (a, b) in edges:
        assert (b, a) not in edges, (a, b)
    dot = racelint.lock_order_dot()
    assert dot.startswith("digraph") and "SchedulerServer._lock" in dot


# ------------------------------------------- rule 1: unguarded-field -------


def test_unguarded_field_rejects_and_accepts():
    bad = _lint(
        """
        class C:
            def __init__(self):
                self._lock = threading.Lock()
                self.x = 0
            def set(self, v):
                with self._lock:
                    self.x = v
            def peek(self):
                return self.x
        """
    )
    assert [d.rule for d in bad] == ["unguarded-field"]
    assert "C.x" in bad[0].message and bad[0].function == "peek"
    ok = _lint(
        """
        class C:
            def __init__(self):
                self._lock = threading.Lock()
                self.x = 0
            def set(self, v):
                with self._lock:
                    self.x = v
            def peek(self):
                with self._lock:
                    return self.x
        """
    )
    assert ok == []


def test_unguarded_module_global():
    bad = _lint(
        """
        _LOCK = threading.Lock()
        _STATE = {}
        def put(k, v):
            with _LOCK:
                _STATE[k] = v
        def peek(k):
            return _STATE.get(k)
        """
    )
    assert [d.rule for d in bad] == ["unguarded-field"]
    assert "_STATE" in bad[0].message


def test_init_is_exempt():
    ok = _lint(
        """
        class C:
            def __init__(self):
                self._lock = threading.Lock()
                self.x = 0  # construction is single-threaded
            def bump(self):
                with self._lock:
                    self.x += 1
        """
    )
    assert ok == []


# ---------------------------------------- rule 2: lock-order cycles --------


_CYCLE = """
class A:
    def __init__(self):
        self._lock = threading.Lock()
        self.b = B()
    def m1(self):
        with self._lock:
            self.b.m2()
class B:
    def __init__(self):
        self._lock = threading.Lock()
        self.a = A()
    def m2(self):
        with self._lock:
            pass
    def m3(self):
        with self._lock:
            self.a.m1()
"""


def test_lock_order_cycle_rejected_and_acyclic_accepted():
    bad = _lint(_CYCLE)
    assert any(d.rule == "lock-order-cycle" for d in bad), bad
    ok = _lint(_CYCLE.replace(
        "    def m3(self):\n        with self._lock:\n            self.a.m1()\n",
        "",
    ))
    assert [d for d in ok if d.rule == "lock-order-cycle"] == []


def test_non_reentrant_reacquire_flagged():
    bad = _lint(
        """
        class C:
            def __init__(self):
                self._lock = threading.Lock()
            def outer(self):
                with self._lock:
                    self.inner()
            def inner(self):
                with self._lock:
                    pass
        """
    )
    assert any(
        d.rule == "lock-order-cycle" and "non-reentrant" in d.message
        for d in bad
    ), bad
    # the same shape on an RLock is legal re-entrancy
    ok = _lint(
        """
        class C:
            def __init__(self):
                self._lock = threading.RLock()
            def outer(self):
                with self._lock:
                    self.inner()
            def inner(self):
                with self._lock:
                    pass
        """
    )
    assert [d for d in ok if d.rule == "lock-order-cycle"] == []


# -------------------------------------- rule 3: blocking under lock --------


def test_blocking_under_lock_direct_and_transitive():
    bad = _lint(
        """
        class C:
            def __init__(self):
                self._lock = threading.Lock()
            def direct(self):
                with self._lock:
                    time.sleep(0.1)
            def helper(self):
                time.sleep(0.1)
            def transitive(self):
                with self._lock:
                    self.helper()
        """
    )
    rules = [d.rule for d in bad]
    assert rules.count("blocking-under-lock") == 2, bad
    ok = _lint(
        """
        class C:
            def __init__(self):
                self._lock = threading.Lock()
            def fine(self):
                with self._lock:
                    x = 1
                time.sleep(0.1)
        """
    )
    assert ok == []


def test_blocking_queue_put_under_lock_flagged():
    """The PR 3 deadlock shape: a bounded-queue put while holding a lock
    the consumer thread needs."""
    bad = _lint(
        """
        import queue
        class C:
            def __init__(self):
                self._lock = threading.Lock()
                self._q = queue.Queue(maxsize=10)
            def post(self, event):
                with self._lock:
                    self._q.put(event)
        """
    )
    assert [d.rule for d in bad] == ["blocking-under-lock"]
    # KV-store put(key, value) is NOT a queue put
    ok = _lint(
        """
        class C:
            def __init__(self):
                self._lock = threading.Lock()
                self.backend = None
            def save(self, k, v):
                with self._lock:
                    self.backend.put(k, v)
        """
    )
    assert ok == []


# ------------------------------------ rule 4: undeclared transitions -------


def test_undeclared_task_transition_rejected():
    bad = _lint(
        """
        class TaskState:
            pass
        def f(t):
            if t.state == TaskState.PENDING:
                t.state = TaskState.COMPLETED
        """
    )
    assert [d.rule for d in bad] == ["undeclared-transition"]
    assert "pending -> completed" in bad[0].message


def test_declared_task_transition_accepted():
    ok = _lint(
        """
        class TaskState:
            pass
        def f(t):
            if t.state == TaskState.RUNNING:
                t.state = TaskState.PENDING
        """
    )
    assert ok == []


def test_dynamic_assignment_requires_table_guard():
    bad = _lint(
        """
        class TaskState:
            pass
        def f(t, new_state):
            t.state = new_state
        """
    )
    assert [d.rule for d in bad] == ["undeclared-transition"]
    ok = _lint(
        """
        class TaskState:
            pass
        _LEGAL = set()
        def f(t, new_state):
            if (t.state, new_state) not in _LEGAL:
                return
            t.state = new_state
        """
    )
    assert ok == []


def test_undeclared_job_state_rejected():
    bad = _lint(
        """
        def f(job):
            job.status = "zombie"
        """
    )
    assert [d.rule for d in bad] == ["undeclared-transition"]
    ok = _lint(
        """
        def f(job):
            job.status = "failed"
        """
    )
    assert ok == []


# ------------------------------------------------------- suppression -------


def test_suppression_line_and_function_scope():
    ok = _lint(
        """
        class C:
            def __init__(self):
                self._lock = threading.Lock()
                self.x = 0
            def set(self, v):
                with self._lock:
                    self.x = v
            def peek(self):
                return self.x  # racelint: disable=unguarded-field
        """
    )
    assert ok == []
    ok2 = _lint(
        """
        class C:
            def __init__(self):
                self._lock = threading.Lock()
                self.x = 0
            def set(self, v):
                with self._lock:
                    self.x = v
            def peek(self):  # racelint: disable=all
                return self.x
        """
    )
    assert ok2 == []


# --------------------------------------------------- state machine ---------


def test_tables_govern_runtime_validator():
    """stage_manager._LEGAL is DERIVED from the declared table — code and
    spec cannot drift."""
    from ballista_tpu.scheduler.stage_manager import _LEGAL

    assert {(a.value, b.value) for a, b in _LEGAL} == set(TASK_TRANSITIONS)


def test_tables_render_and_cover_states():
    text = render_tables()
    assert "task transitions" in text and "job transitions" in text
    assert ("completed", "pending") in TASK_TRANSITIONS  # lost-shuffle
    assert ("completed", "running") in STAGE_TRANSITIONS  # rollback
    assert ("running", "failed") in JOB_TRANSITIONS


# ------------------------------------------------------ runtime witness ----


def test_witness_records_orders_and_flags_inversion():
    witness.reset()
    witness.enable(True)
    try:
        a = witness.make_lock("T.A")
        b = witness.make_lock("T.B")
        with a:
            with b:
                pass
        assert ("T.A", "T.B") in witness.edges()
        assert witness.violations() == []
        witness.assert_consistent([("T.A", "T.B")])
        # the static graph ordering B before A would be an inversion
        with pytest.raises(AssertionError):
            witness.assert_consistent([("T.B", "T.A")])

        # live inversion from another thread
        def invert():
            with b:
                with a:
                    pass

        t = threading.Thread(target=invert)
        t.start()
        t.join()
        assert witness.violations(), "B->A after A->B must be flagged"
    finally:
        witness.enable(False)
        witness.reset()


def test_witness_reentrant_lock_records_no_self_edge():
    witness.reset()
    witness.enable(True)
    try:
        a = witness.make_lock("T.R", reentrant=True)
        with a:
            with a:
                pass
        assert ("T.R", "T.R") not in witness.edges()
        assert witness.violations() == []
    finally:
        witness.enable(False)
        witness.reset()


def test_witness_disabled_returns_plain_locks():
    assert not witness.enabled()
    lk = witness.make_lock("T.plain")
    assert not isinstance(lk, witness.TracedLock)


# ------------------------------------------------------ combined gate ------


def test_combined_analysis_gate_is_clean():
    """`python -m ballista_tpu.analysis` aggregates planlint + serde-audit
    + jaxlint + racelint into one exit code, with a summary line per
    analyzer. planlint runs a TPC-H subset here — the full corpus is
    tier-1 via test_plan_verifier.py."""
    from ballista_tpu.analysis.__main__ import run_all

    lines: list[str] = []
    rc = run_all(queries=[1, 3, 6], out=lines.append)
    assert rc == 0, "\n".join(lines)
    for name in (
        "planlint", "serde-audit", "jaxlint", "racelint", "compile-vocab",
        "lifelint", "proto-drift", "config-registry",
    ):
        assert any(ln.startswith(f"{name}: OK") for ln in lines), lines


def test_cli_dot_and_tables_flags(capsys):
    from ballista_tpu.analysis.__main__ import main

    assert main(["--dot"]) == 0
    assert "digraph lock_order" in capsys.readouterr().out
    assert main(["--tables"]) == 0
    assert "task transitions" in capsys.readouterr().out
