"""EtcdBackend against an in-process fake etcd speaking the real wire
protocol (ref ballista/rust/scheduler/src/state/backend/etcd.rs:32-196;
no etcd binary ships in this image, so the server side is a faithful
dict-backed stand-in registered under the genuine etcd service paths —
the client under test is byte-for-byte what would talk to a real
cluster)."""

from __future__ import annotations

import itertools
import threading
import time
from concurrent import futures

import grpc
import pytest

from ballista_tpu.proto import etcd_pb2 as epb
from ballista_tpu.scheduler.etcd_backend import EtcdBackend, prefix_end


class FakeEtcd:
    """KV + Watch + Lease + v3 Lock over a dict, mod-revision tracked."""

    def __init__(self) -> None:
        self._kv: dict[bytes, bytes] = {}
        self._rev = 0
        self._mu = threading.Lock()
        self._watches: list[tuple[bytes, bytes, object]] = []
        self._lease_ids = itertools.count(1)
        self._lock_cv = threading.Condition()
        self._lock_holder: bytes | None = None

    # -- KV ----------------------------------------------------------------
    def _in_range(self, k: bytes, key: bytes, range_end: bytes) -> bool:
        if not range_end:
            return k == key
        if range_end == b"\x00":
            return k >= key
        return key <= k < range_end

    def Range(self, req: epb.RangeRequest, ctx) -> epb.RangeResponse:
        with self._mu:
            kvs = sorted(
                (k, v) for k, v in self._kv.items()
                if self._in_range(k, req.key, req.range_end)
            )
            resp = epb.RangeResponse(count=len(kvs))
            resp.header.revision = self._rev
            for k, v in kvs:
                resp.kvs.add(key=k, value=v, mod_revision=self._rev)
            return resp

    def _broadcast(self, ev: epb.Event) -> None:
        for key, range_end, q in list(self._watches):
            if self._in_range(ev.kv.key, key, range_end):
                q.append(ev)

    def Put(self, req: epb.PutRequest, ctx) -> epb.PutResponse:
        with self._mu:
            self._rev += 1
            self._kv[req.key] = req.value
            ev = epb.Event(type=epb.Event.PUT)
            ev.kv.key, ev.kv.value = req.key, req.value
            ev.kv.mod_revision = self._rev
            self._broadcast(ev)
            resp = epb.PutResponse()
            resp.header.revision = self._rev
            return resp

    def DeleteRange(self, req: epb.DeleteRangeRequest, ctx):
        with self._mu:
            gone = [k for k in self._kv
                    if self._in_range(k, req.key, req.range_end)]
            self._rev += 1
            for k in gone:
                del self._kv[k]
                ev = epb.Event(type=epb.Event.DELETE)
                ev.kv.key = k
                ev.kv.mod_revision = self._rev
                self._broadcast(ev)
            resp = epb.DeleteRangeResponse(deleted=len(gone))
            resp.header.revision = self._rev
            return resp

    # -- Watch (bidi) ------------------------------------------------------
    def Watch(self, request_iter, ctx):
        sub: list | None = None
        try:
            req = next(request_iter)
        except StopIteration:
            return
        if req.HasField("create_request"):
            cr = req.create_request
            sub = []
            with self._mu:
                self._watches.append((cr.key, cr.range_end, sub))
            yield epb.WatchResponse(watch_id=1, created=True)
            try:
                while ctx.is_active():
                    if sub:
                        resp = epb.WatchResponse(watch_id=1)
                        while sub:
                            resp.events.append(sub.pop(0))
                        yield resp
                    else:
                        time.sleep(0.01)
            finally:
                with self._mu:
                    self._watches = [
                        w for w in self._watches if w[2] is not sub
                    ]

    # -- Lease + Lock ------------------------------------------------------
    def LeaseGrant(self, req, ctx):
        return epb.LeaseGrantResponse(ID=next(self._lease_ids), TTL=req.TTL)

    def LeaseRevoke(self, req, ctx):
        return epb.LeaseRevokeResponse()

    def Lock(self, req: epb.LockRequest, ctx):
        key = req.name + b"/%d" % req.lease
        with self._lock_cv:
            while self._lock_holder is not None:
                self._lock_cv.wait()
            self._lock_holder = key
        return epb.LockResponse(key=key)

    def Unlock(self, req: epb.UnlockRequest, ctx):
        with self._lock_cv:
            if self._lock_holder == req.key:
                self._lock_holder = None
                self._lock_cv.notify_all()
        return epb.UnlockResponse()


def _serve(fake: FakeEtcd):
    server = grpc.server(futures.ThreadPoolExecutor(max_workers=8))

    def unary(fn, req_cls):
        return grpc.unary_unary_rpc_method_handler(
            fn, request_deserializer=req_cls.FromString,
            response_serializer=lambda r: r.SerializeToString())

    server.add_generic_rpc_handlers((
        grpc.method_handlers_generic_handler("etcdserverpb.KV", {
            "Range": unary(fake.Range, epb.RangeRequest),
            "Put": unary(fake.Put, epb.PutRequest),
            "DeleteRange": unary(fake.DeleteRange, epb.DeleteRangeRequest),
        }),
        grpc.method_handlers_generic_handler("etcdserverpb.Watch", {
            "Watch": grpc.stream_stream_rpc_method_handler(
                fake.Watch,
                request_deserializer=epb.WatchRequest.FromString,
                response_serializer=lambda r: r.SerializeToString()),
        }),
        grpc.method_handlers_generic_handler("etcdserverpb.Lease", {
            "LeaseGrant": unary(fake.LeaseGrant, epb.LeaseGrantRequest),
            "LeaseRevoke": unary(fake.LeaseRevoke, epb.LeaseRevokeRequest),
        }),
        grpc.method_handlers_generic_handler("v3lockpb.Lock", {
            "Lock": unary(fake.Lock, epb.LockRequest),
            "Unlock": unary(fake.Unlock, epb.UnlockRequest),
        }),
    ))
    port = server.add_insecure_port("127.0.0.1:0")
    server.start()
    return server, f"127.0.0.1:{port}"


@pytest.fixture()
def etcd():
    server, url = _serve(FakeEtcd())
    yield url
    server.stop(grace=None)


def test_prefix_end():
    assert prefix_end(b"/ballista/") == b"/ballista0"
    assert prefix_end(b"a\xff") == b"b"
    assert prefix_end(b"\xff\xff") == b"\x00"  # whole keyspace


def test_etcd_kv_and_prefix(etcd):
    be = EtcdBackend(etcd)
    assert be.get("/ballista/jobs/j1") is None
    be.put("/ballista/jobs/j1", b"queued")
    be.put("/ballista/jobs/j2", b"running")
    be.put("/ballista/executors/e1", b"alive")
    assert be.get("/ballista/jobs/j1") == b"queued"
    assert be.get_from_prefix("/ballista/jobs/") == [
        ("/ballista/jobs/j1", b"queued"),
        ("/ballista/jobs/j2", b"running"),
    ]
    be.delete("/ballista/jobs/j1")
    assert be.get("/ballista/jobs/j1") is None
    be.close()


def test_etcd_watch_sees_other_clients(etcd):
    """The property the embedded backends cannot give: a watch on one
    scheduler observes writes made by ANOTHER scheduler process."""
    a, b = EtcdBackend(etcd), EtcdBackend(etcd)
    w = a.watch("/ballista/jobs/")  # blocks until the server acks created
    b.put("/ballista/jobs/j1", b"queued")
    b.put("/ballista/other/x", b"ignored")
    b.delete("/ballista/jobs/j1")
    e1 = w.get(timeout=2)
    assert (e1.kind, e1.key, e1.value) == ("put", "/ballista/jobs/j1",
                                           b"queued")
    e2 = w.get(timeout=2)
    assert (e2.kind, e2.value) == ("delete", None)
    assert w.get(timeout=0.05) is None
    w.stop()
    a.close()
    b.close()


def test_etcd_global_lock_mutual_exclusion(etcd):
    a, b = EtcdBackend(etcd), EtcdBackend(etcd)
    order: list[str] = []
    entered = threading.Event()
    release = threading.Event()

    def holder():
        with a.lock():
            order.append("a-in")
            entered.set()
            release.wait(timeout=5)
            order.append("a-out")

    t = threading.Thread(target=holder)
    t.start()
    assert entered.wait(timeout=5)
    t2_done = threading.Event()

    def contender():
        with b.lock():
            order.append("b-in")
            t2_done.set()

    t2 = threading.Thread(target=contender)
    t2.start()
    time.sleep(0.2)
    assert "b-in" not in order  # blocked while a holds it
    release.set()
    assert t2_done.wait(timeout=5)
    t.join(timeout=5)
    t2.join(timeout=5)
    assert order == ["a-in", "a-out", "b-in"]
    a.close()
    b.close()


def test_persistent_state_over_etcd(etcd):
    """Scheduler restart recovery through etcd: state written by one
    'scheduler' instance is re-initialized by a fresh one pointed at the
    same cluster (ref persistent_state.rs:401-525 exercised over the
    etcd backend instead of sled)."""
    from ballista_tpu.scheduler.persistent_state import (
        PersistentSchedulerState,
    )
    from ballista_tpu.scheduler_types import (
        ExecutorMetadata,
        ExecutorSpecification,
    )

    be = EtcdBackend(etcd)
    st = PersistentSchedulerState(be, namespace="t")
    st.save_executor_metadata(ExecutorMetadata(
        id="e1", host="h", port=1, grpc_port=2,
        specification=ExecutorSpecification(task_slots=4)))
    be.close()

    be2 = EtcdBackend(etcd)
    st2 = PersistentSchedulerState(be2, namespace="t")
    metas = st2.load_executors()
    assert [(m.id, m.specification.task_slots) for m in metas] == [("e1", 4)]
    be2.close()
