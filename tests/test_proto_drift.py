"""Wire-drift gate: proto TEXT ↔ generated DESCRIPTOR ↔ committed
field-number ledger (ISSUE 8 satellite).

The image has no protoc, so PRs 2/6/7 edited the wire format by mutating
the serialized descriptor inside ballista_tpu/proto/*_pb2.py and hand-
syncing proto/*.proto. These tests make that sync mechanical: the parsed
.proto text must agree with the live descriptor pool on every message /
field / number / label / type / enum / RPC signature, and
proto/field_numbers.json pins every number ever assigned (no renumber,
no reuse of retired numbers, new fields appended in the same commit).
"""

import copy
import json
import textwrap

from ballista_tpu.analysis import protodrift
from ballista_tpu.proto import ballista_tpu_pb2, etcd_pb2


def _ledger():
    return json.loads(protodrift.ledger_path().read_text())


# ------------------------------------------------------------ tier-1 gate --


def test_proto_text_descriptor_and_ledger_in_sync():
    ok, msg = protodrift.run()
    assert ok, msg


def test_ledger_file_matches_generated_content():
    """The committed ledger must be exactly what the descriptor implies
    plus (possibly) retired entries — i.e. regenerating adds nothing."""
    committed = _ledger()
    generated = protodrift.generate_ledger()
    for pkg, msgs in generated.items():
        assert pkg in committed, pkg
        for msg, fields in msgs.items():
            if msg == "__retired__":
                continue
            assert committed[pkg].get(msg) == fields, msg


def test_known_wire_surface_is_covered():
    """Spot anchors: the descriptor model sees the PR 6/7 descriptor-
    mutated additions, so the diff genuinely covers them."""
    desc = protodrift.descriptor_model(ballista_tpu_pb2)
    assert "PhysicalMeshWindowNode" in desc.messages  # PR 2 mutation
    assert "ShuffleLocationsResult" in desc.messages  # PR 6 mutation
    assert desc.messages["ShuffleReaderExecNode"]["eager"][0] == 5
    assert "metrics" in desc.messages["PollWorkParams"]  # PR 7 mutation
    assert "GetShuffleLocations" in desc.services["SchedulerGrpc"]
    # etcd streams carry their streaming flags
    e = protodrift.descriptor_model(etcd_pb2)
    assert e.services["Watch"]["Watch"][2:] == (True, True)


# ------------------------------------------------------- text-side drift --

_MINI = textwrap.dedent(
    """
    syntax = "proto3";
    package mini;
    enum Kind {
      K_A = 0;
      K_B = 1;
    }
    message Inner {
      string tag = 1;
    }
    message Outer {
      message Nested { bool on = 1; }
      repeated Inner items = 1;
      Kind kind = 2;
      oneof which {
        int64 num = 3;
        string name = 4;
      }
      map<string, string> attrs = 5;
    }
    service Svc {
      rpc Get (Inner) returns (stream Outer) {}
    }
    """
)


def test_text_parser_covers_the_grammar():
    m = protodrift.parse_proto_text(_MINI)
    assert m.package == "mini"
    assert m.messages["Outer"]["items"] == (1, True, "Inner")
    assert m.messages["Outer"]["kind"] == (2, False, "Kind")
    assert m.messages["Outer"]["num"] == (3, False, "int64")  # oneof
    assert m.messages["Outer"]["attrs"] == (
        5, False, "map<string,string>"
    )
    assert m.messages["Outer.Nested"]["on"] == (1, False, "bool")
    assert m.enums["Kind"] == {"K_A": 0, "K_B": 1}
    assert m.services["Svc"]["Get"] == ("Inner", "Outer", False, True)


def test_diff_detects_each_drift_class():
    base = protodrift.parse_proto_text(_MINI)

    def mutated(fn):
        m = copy.deepcopy(base)
        fn(m)
        return protodrift.diff_models(base, m)

    # field renumber
    d = mutated(lambda m: m.messages["Outer"].update(
        items=(9, True, "Inner")
    ))
    assert any("NUMBER drift" in p for p in d), d
    # type change
    d = mutated(lambda m: m.messages["Inner"].update(
        tag=(1, False, "bytes")
    ))
    assert any("type drift" in p for p in d), d
    # repeated flip
    d = mutated(lambda m: m.messages["Outer"].update(
        items=(1, False, "Inner")
    ))
    assert any("repeated-label drift" in p for p in d), d
    # removed field
    d = mutated(lambda m: m.messages["Inner"].pop("tag"))
    assert any("in proto text only" in p for p in d), d
    # added message
    d = mutated(lambda m: m.messages.update(Ghost={}))
    assert any("NOT in proto text" in p for p in d), d
    # enum value drift
    d = mutated(lambda m: m.enums["Kind"].update(K_B=7))
    assert any("enum Kind" in p for p in d), d
    # rpc signature drift (streaming flag)
    d = mutated(lambda m: m.services["Svc"].update(
        Get=("Inner", "Outer", False, False)
    ))
    assert any("signature drift" in p for p in d), d
    # no drift -> no findings
    assert protodrift.diff_models(base, copy.deepcopy(base)) == []


# --------------------------------------------------------- ledger rules --


def test_ledger_rejects_renumber_rename_remove_and_reuse():
    good = protodrift.generate_ledger()

    def run_with(mut):
        led = copy.deepcopy(good)
        mut(led)
        ok, msg = protodrift.run(ledger=led)
        return ok, msg

    ok, msg = run_with(lambda led: None)
    assert ok, msg

    ok, msg = run_with(
        lambda led: led["ballista_tpu"]["FieldP"].update(name=42)
    )
    assert not ok and "RENUMBERED" in msg

    # descriptor field absent from the ledger = unappended new field
    ok, msg = run_with(
        lambda led: led["ballista_tpu"]["FieldP"].pop("dtype")
    )
    assert not ok and "not in the ledger" in msg

    # ledger field absent from the descriptor = silent removal
    ok, msg = run_with(
        lambda led: led["ballista_tpu"]["FieldP"].update(ghost_field=7)
    )
    assert not ok and "gone from the descriptor" in msg

    # retired number reused by a live field of another name
    ok, msg = run_with(
        lambda led: led["ballista_tpu"].update(
            __retired__={"FieldP": {"old_name": 1}}
        )
    )
    assert not ok and "REUSES retired number" in msg

    # whole message missing from the ledger
    ok, msg = run_with(lambda led: led["ballista_tpu"].pop("SchemaP"))
    assert not ok and "missing from the field-number ledger" in msg
