"""Adaptive aggregate-capacity retry (VERDICT r2 Weak#1 regression).

The round-2 bench failed at its own default scale because q18's
``GROUP BY l_orderkey`` produced more groups than the fixed
``ballista.tpu.agg_capacity``. The engine now reports the exact required
group count on overflow (the sort-based kernel computes the true count
regardless of capacity) and the execution driver retries with a grown
capacity instead of failing.
"""

import numpy as np
import pyarrow as pa
import pytest

from ballista_tpu.config import BallistaConfig
from ballista_tpu.errors import CapacityError
from ballista_tpu.exec.context import TpuContext


def _ctx_small_cap(cap: int) -> TpuContext:
    cfg = BallistaConfig().with_setting("ballista.tpu.agg_capacity", str(cap))
    return TpuContext(cfg)


def test_group_count_exceeding_capacity_retries_and_succeeds():
    n, n_groups = 20_000, 3_000  # groups >> capacity of 256
    rng = np.random.default_rng(3)
    keys = rng.integers(0, n_groups, n)
    vals = rng.uniform(0, 10, n)
    t = pa.table({"k": pa.array(keys), "v": pa.array(vals)})
    ctx = _ctx_small_cap(256)
    ctx.register_table("t", t)
    out = (
        ctx.sql("SELECT k, SUM(v) AS s, COUNT(*) AS c FROM t GROUP BY k")
        .collect()
        .to_pandas()
        .sort_values("k")
        .reset_index(drop=True)
    )
    want = (
        pa.table({"k": pa.array(keys), "v": pa.array(vals)})
        .to_pandas()
        .groupby("k")
        .agg(s=("v", "sum"), c=("v", "count"))
        .reset_index()
    )
    assert len(out) == len(want)
    np.testing.assert_array_equal(out.k.to_numpy(), want.k.to_numpy())
    np.testing.assert_allclose(out.s.to_numpy(), want.s.to_numpy(), rtol=1e-9)
    np.testing.assert_array_equal(out.c.to_numpy(), want.c.to_numpy())


def test_capacity_error_carries_required_count():
    from ballista_tpu.ops.aggregate import AggOp, group_aggregate
    import jax.numpy as jnp

    n = 1024
    keys = jnp.arange(n, dtype=jnp.int64)  # 1024 distinct groups
    vals = jnp.ones(n)
    res = group_aggregate(
        [keys], [None], jnp.ones(n, dtype=bool), [vals], [None],
        [AggOp.SUM], capacity=16,
    )
    with pytest.raises(CapacityError) as ei:
        res.check_overflow()
    assert ei.value.required == n


def test_scalar_aggregate_unaffected():
    t = pa.table({"v": pa.array(np.arange(100.0))})
    ctx = _ctx_small_cap(16)
    ctx.register_table("t", t)
    out = ctx.sql("SELECT SUM(v) AS s FROM t").collect().to_pandas()
    assert out.s[0] == pytest.approx(4950.0)
