"""Distributed planner golden tests: stage decomposition + serde round-trip.

Mirrors the reference's planner tests (ballista/rust/scheduler/src/
planner.rs:301-561), which pin the exact stage decomposition of TPC-H-like
plans, and the serde round-trip tests (:563-619, compared by display
string).
"""

import pathlib

import pytest

from ballista_tpu.distributed_plan import (
    DistributedPlanner,
    UnresolvedShuffleExec,
    find_unresolved_shuffles,
    remove_unresolved_shuffles,
)
from ballista_tpu.exec.context import TpuContext
from ballista_tpu.executor.reader import ShuffleReaderExec
from ballista_tpu.executor.shuffle import ShuffleWriterExec
from ballista_tpu.scheduler_types import PartitionLocation
from ballista_tpu.serde import BallistaCodec
from ballista_tpu.tpch import gen_all

QDIR = pathlib.Path(__file__).resolve().parent.parent / "benchmarks" / "queries"


@pytest.fixture(scope="module")
def ctx():
    c = TpuContext()
    for name, t in gen_all(scale=0.001).items():
        c.register_table(name, t)
    return c


def _physical(ctx, sql: str):
    return ctx.create_physical_plan(ctx.sql_to_logical(sql))


def test_q1_two_stages(ctx):
    """Aggregate query splits at the coalesce boundary: partial-agg stage +
    terminal stage (the reference's q1 splits into 3 because it also
    repartitions between partial and final, planner.rs:328-344; we coalesce
    partials into one final today, so 2)."""
    phys = _physical(ctx, (QDIR / "q1.sql").read_text())
    stages = DistributedPlanner().plan_query_stages("job1", phys)
    assert len(stages) == 2
    s1, s2 = stages
    assert isinstance(s1.plan, ShuffleWriterExec)
    assert s1.output_partition_count == 1
    assert s1.input_partition_count == 2  # default shuffle partitions
    # terminal stage consumes stage 1 via a placeholder
    unresolved = find_unresolved_shuffles(s2.plan)
    assert len(unresolved) == 1
    assert unresolved[0].stage_id == s1.stage_id


def test_q3_stage_dag(ctx):
    """Join query: each join build side materializes as its own stage."""
    phys = _physical(ctx, (QDIR / "q3.sql").read_text())
    stages = DistributedPlanner().plan_query_stages("job3", phys)
    assert len(stages) >= 4  # 2 join builds + partial agg + terminal
    terminal = stages[-1]
    # every non-terminal stage is consumed by exactly one other stage
    consumed = set()
    for s in stages:
        for u in find_unresolved_shuffles(s.plan):
            consumed.add(u.stage_id)
    produced = {s.stage_id for s in stages[:-1]}
    assert produced == consumed
    assert terminal.output_partition_count == 1


def test_resolve_shuffles(ctx):
    phys = _physical(ctx, (QDIR / "q6.sql").read_text())
    stages = DistributedPlanner().plan_query_stages("job6", phys)
    terminal = stages[-1]
    unresolved = find_unresolved_shuffles(terminal.plan)
    assert unresolved
    locations = {
        u.stage_id: [
            [
                PartitionLocation(
                    job_id="job6",
                    stage_id=u.stage_id,
                    partition=p,
                    executor_id="e1",
                    host="localhost",
                    port=50051,
                    path=f"/tmp/job6/{u.stage_id}/{p}/data-0.arrow",
                )
            ]
            for p in range(u.output_partition_count)
        ]
        for u in unresolved
    }
    resolved = remove_unresolved_shuffles(terminal.plan, locations)
    assert not find_unresolved_shuffles(resolved)
    readers = []

    def walk(p):
        if isinstance(p, ShuffleReaderExec):
            readers.append(p)
        for c in p.children():
            walk(c)

    walk(resolved)
    assert len(readers) == len(unresolved)


@pytest.mark.parametrize("q", ["q1", "q3", "q6", "q12"])
def test_stage_plan_serde_roundtrip(ctx, q):
    """Stage plans round-trip through protobuf compared by display string
    (the reference's roundtrip_operator pattern, planner.rs:563-619)."""
    phys = _physical(ctx, (QDIR / f"{q}.sql").read_text())
    stages = DistributedPlanner().plan_query_stages("jobr", phys)
    codec = BallistaCodec(provider=ctx)
    for stage in stages:
        proto = codec.physical_to_proto(stage.plan)
        data = proto.SerializeToString()
        import ballista_tpu.proto as bp

        node = bp.pb.PhysicalPlanNode()
        node.ParseFromString(data)
        back = codec.physical_from_proto(node)
        assert back.display() == stage.plan.display()


def test_unresolved_shuffle_not_executable(ctx):
    from ballista_tpu.datatypes import Schema
    from ballista_tpu.errors import InternalError
    from ballista_tpu.exec.base import TaskContext

    u = UnresolvedShuffleExec(1, Schema([]), 2, 2)
    with pytest.raises(InternalError):
        list(u.execute(0, TaskContext()))
