"""Clustered-input (presorted) aggregation: the segment-reduction kernel
and its speculation protocol.

The TPU kernel (ops/aggregate.py `_segment_aggregate`) replaces scatter
reductions with cumsum + boundary gathers once rows are grouped-adjacent;
`presorted=True` additionally skips the sort and gather. The exec layer
learns clusteredness off the stable sort's permutation and validates the
fast path with a deferred flag (ref behavior: DataFusion's ordered-input
aggregation; the wire shape is the same HashAggregateExecNode,
ballista.proto:446-455 — clustering is purely an execution-time detail).
"""

import numpy as np
import pandas as pd
import pytest

import jax.numpy as jnp

from ballista_tpu.ops.aggregate import AggOp, group_aggregate


def _oracle(keys, vals, valid, op):
    df = pd.DataFrame({"k": keys, "v": vals, "ok": valid})
    df = df[df.ok]
    if op == "sum":
        return df.groupby("k").v.sum()
    if op == "min":
        return df.groupby("k").v.min()
    if op == "max":
        return df.groupby("k").v.max()
    return df.groupby("k").v.count()


@pytest.mark.parametrize("presorted", [False, True])
def test_clustered_sum_count_min_max(presorted):
    rng = np.random.default_rng(7)
    n = 4096
    keys = np.sort(rng.integers(0, 300, n)).astype(np.int64)
    vals = rng.random(n) * 100
    ivals = rng.integers(-50, 50, n).astype(np.int64)
    valid = rng.random(n) < 0.6  # interspersed invalid rows
    res = group_aggregate(
        [jnp.asarray(keys)],
        [None],
        jnp.asarray(valid),
        [jnp.asarray(vals), jnp.asarray(ivals), jnp.asarray(vals),
         jnp.asarray(ivals)],
        [None, None, None, None],
        [AggOp.SUM, AggOp.SUM, AggOp.MIN, AggOp.MAX],
        1024,
        presorted=presorted,
    )
    if presorted:
        assert bool(res.sorted_ok)
    else:
        assert bool(res.input_was_sorted)
    ok = np.asarray(res.valid)
    got_keys = np.asarray(res.keys[0])[ok]
    o_sum = _oracle(keys, vals, valid, "sum")
    assert sorted(got_keys) == sorted(o_sum.index)
    order = {g: i for i, g in enumerate(got_keys)}
    gv = np.asarray(res.values[0])[ok]
    np.testing.assert_allclose(
        [gv[order[g]] for g in o_sum.index], o_sum.values, rtol=1e-7
    )
    o_isum = _oracle(keys, ivals, valid, "sum")
    giv = np.asarray(res.values[1])[ok]
    assert [giv[order[g]] for g in o_isum.index] == list(o_isum.values)
    o_min = _oracle(keys, vals, valid, "min")
    gmn = np.asarray(res.values[2])[ok]
    np.testing.assert_allclose(
        [gmn[order[g]] for g in o_min.index], o_min.values
    )
    o_max = _oracle(keys, ivals, valid, "max")
    gmx = np.asarray(res.values[3])[ok]
    assert [gmx[order[g]] for g in o_max.index] == list(o_max.values)


def test_presorted_flags_unsorted_input():
    """sorted_ok must come back False when the speculation is wrong."""
    keys = jnp.asarray(np.array([5, 1, 5, 1, 2, 2], dtype=np.int64))
    vals = jnp.asarray(np.ones(6))
    valid = jnp.asarray(np.ones(6, bool))
    res = group_aggregate(
        [keys], [None], valid, [vals], [None], [AggOp.SUM], 8,
        presorted=True,
    )
    assert not bool(res.sorted_ok)
    # and the sort path reports the input as NOT clustered
    res2 = group_aggregate(
        [keys], [None], valid, [vals], [None], [AggOp.SUM], 8,
    )
    assert not bool(res2.input_was_sorted)
    ok = np.asarray(res2.valid)
    assert sorted(np.asarray(res2.keys[0])[ok]) == [1, 2, 5]


def test_clustered_null_keys_and_values():
    """NULL keys form their own group; NULL values are skipped; an
    all-NULL group yields NULL sum (SQL) in both paths."""
    keys = np.array([1, 1, 2, 2, 3, 3], dtype=np.int64)
    knull = np.array([False, False, False, False, True, True])
    vals = np.array([1.0, 2.0, 9.0, 9.0, 5.0, 6.0])
    vnull = np.array([False, False, True, True, False, False])
    valid = np.ones(6, bool)
    for presorted in (False, True):
        res = group_aggregate(
            [jnp.asarray(keys)],
            [jnp.asarray(knull)],
            jnp.asarray(valid),
            [jnp.asarray(vals)],
            [jnp.asarray(vnull)],
            [AggOp.SUM],
            8,
            presorted=presorted,
        )
        ok = np.asarray(res.valid)
        assert int(ok.sum()) == 3
        got = {}
        kn = np.asarray(res.key_nulls[0])
        for i in np.nonzero(ok)[0]:
            k = "NULL" if kn[i] else int(np.asarray(res.keys[0])[i])
            got[k] = (
                None
                if np.asarray(res.value_nulls[0])[i]
                else float(np.asarray(res.values[0])[i])
            )
        assert got[1] == 3.0
        assert got[2] is None  # all values NULL -> SUM is NULL
        assert got["NULL"] == 11.0


def test_presorted_overflow_reports_group_count():
    keys = jnp.asarray(np.arange(64, dtype=np.int64))
    res = group_aggregate(
        [keys], [None], jnp.asarray(np.ones(64, bool)),
        [jnp.asarray(np.ones(64))], [None], [AggOp.SUM], 16,
        presorted=True,
    )
    assert bool(res.overflow)
    assert int(res.n_groups) == 64


def test_engine_learns_clustered_path(tmp_path):
    """End-to-end: a clustered GROUP BY learns the fast path on run 1,
    uses it (validated) on run 2, and both runs agree with the oracle."""
    import pyarrow as pa

    from ballista_tpu.config import BallistaConfig
    from ballista_tpu.exec.context import TpuContext

    rng = np.random.default_rng(3)
    n = 5000
    k = np.sort(rng.integers(0, 800, n))
    v = rng.random(n) * 10
    t = pa.table({"k": pa.array(k, pa.int64()), "v": pa.array(v)})
    ctx = TpuContext(BallistaConfig())
    ctx.register_table("t", t)
    sql = "select k, sum(v) as s, count(*) as c from t group by k"
    r1 = ctx.sql(sql).collect().to_pandas().set_index("k").sort_index()
    # the clustered flag must now be cached for the partial-agg site
    learned = [
        key for key in ctx._plan_cache if key[0] == "agg_sorted"
    ]
    assert learned, "no clusteredness learned"
    assert any(ctx._plan_cache[key] is True for key in learned)
    r2 = ctx.sql(sql).collect().to_pandas().set_index("k").sort_index()
    oracle = (
        pd.DataFrame({"k": k, "v": v})
        .groupby("k")
        .agg(s=("v", "sum"), c=("v", "count"))
    )
    for r in (r1, r2):
        np.testing.assert_allclose(r["s"], oracle["s"], rtol=1e-7)
        assert list(r["c"]) == list(oracle["c"])


def test_state_slice_respects_masked_repartition():
    """A final aggregate fed by an in-place-masking hash repartition gets
    states whose live groups are NOT prefix-compacted; the learned
    state-slice must detect that (prefix flag) and never drop groups.
    Two runs: learn, then the run that would slice if it (wrongly) could."""
    import pyarrow as pa

    from ballista_tpu.config import BallistaConfig
    from ballista_tpu.exec.context import TpuContext

    rng = np.random.default_rng(11)
    n = 20_000
    k = rng.integers(0, 5000, n)  # many groups -> masked repartition states
    v = rng.random(n)
    t = pa.table({"k": pa.array(k, pa.int64()), "v": pa.array(v)})
    ctx = TpuContext(
        BallistaConfig().with_setting("ballista.shuffle.partitions", "4")
    )
    ctx.register_table("t", t)
    sql = "select k, sum(v) as s, count(*) as c from t group by k"
    oracle = (
        pd.DataFrame({"k": k, "v": v})
        .groupby("k")
        .agg(s=("v", "sum"), c=("v", "count"))
    )
    for run in (1, 2):
        r = (
            ctx.sql(sql).collect().to_pandas().set_index("k").sort_index()
        )
        assert len(r) == len(oracle), f"run {run} dropped groups"
        np.testing.assert_allclose(r["s"], oracle["s"], rtol=1e-7)
        assert list(r["c"]) == list(oracle["c"])


def test_engine_speculation_miss_recovers(tmp_path):
    """Poison the cache with a wrong 'clustered' claim: the run must
    detect it (SpeculationMiss -> invalidate -> retry) and still return
    correct results."""
    import pyarrow as pa

    from ballista_tpu.config import BallistaConfig
    from ballista_tpu.exec.context import TpuContext

    rng = np.random.default_rng(4)
    n = 3000
    k = rng.integers(0, 500, n)  # NOT clustered
    v = rng.random(n)
    t = pa.table({"k": pa.array(k, pa.int64()), "v": pa.array(v)})
    ctx = TpuContext(BallistaConfig())
    ctx.register_table("t", t)
    sql = "select k, sum(v) as s from t group by k"
    ctx.sql(sql).collect()  # learn (False expected)
    # force-poison every agg_sorted entry to True
    poisoned = 0
    for key in list(ctx._plan_cache):
        if key[0] == "agg_sorted":
            ctx._plan_cache[key] = True
            poisoned += 1
    assert poisoned
    out = ctx.sql(sql).collect().to_pandas().set_index("k").sort_index()
    oracle = pd.DataFrame({"k": k, "v": v}).groupby("k").v.sum()
    np.testing.assert_allclose(out["s"], oracle.values, rtol=1e-7)
    # the poisoned entries were invalidated back to the truth
    for key in list(ctx._plan_cache):
        if key[0] == "agg_sorted":
            assert ctx._plan_cache[key] is not True
