"""Executor-lost recovery: a task stuck RUNNING on a dead executor is reset
to PENDING by the heartbeat-expiry sweep and re-run on a live executor, so
the job still completes.

Mirrors the reference's liveness filtering (executor_manager.rs:55-77) plus
the RUNNING->PENDING reset transition (stage_manager.rs:553-558) that the
reference declares legal; here the sweep actually invokes it.
"""

import subprocess
import sys

from tests.conftest import CPU_MESH_ENV

SCRIPT = r"""
import time

import numpy as np
import pyarrow as pa

from ballista_tpu.client.context import BallistaContext
from ballista_tpu.config import BallistaConfig
from ballista_tpu.scheduler_types import ExecutorData, ExecutorMetadata
from ballista_tpu.standalone import StandaloneCluster

cfg = BallistaConfig().with_setting("ballista.shuffle.partitions", "3")
ctx = BallistaContext.standalone(cfg)
cluster = ctx._standalone_cluster
sched = cluster.scheduler
# tight liveness window so the test runs in seconds
sched.executor_timeout_s = 1.5

n = 8000
r = np.random.default_rng(5)
t = pa.table({
    "k": pa.array(r.integers(0, 50, n)),
    "v": pa.array(r.uniform(0, 100, n)),
})
ctx.register_table("t", t)

# freeze the real executor so the zombie can grab a task deterministically
cluster.poll_loop.stop()

# a zombie executor registers, heartbeats once, takes a task, and dies
sched.executor_manager.save_executor_metadata(
    ExecutorMetadata(id="zombie", host="localhost", port=1)
)
sched.executor_manager.save_executor_heartbeat("zombie")
sched.executor_manager.save_executor_data(ExecutorData("zombie", 4, 4))

session_id = sched.get_or_create_session("", {})
job_id = sched.submit_sql(
    "select k, sum(v) as sv, count(*) as n from t group by k", session_id
)
sched.event_loop.drain()
td = sched.next_task("zombie")
assert td is not None, "zombie failed to grab a task"
stuck = (td.task_id.job_id, td.task_id.stage_id, td.task_id.partition_id)

# bring a live executor back online (fresh poll loop, same executor state)
from ballista_tpu.executor.executor import PollLoop
loop2 = PollLoop(
    cluster.executor,
    f"localhost:{cluster.scheduler_port}",
    "localhost",
    cluster.flight_port,
    task_slots=4,
)
loop2.start()

# without recovery the job hangs forever on the zombie's RUNNING task;
# the expiry sweep must reset it and let the live executor finish
deadline = time.time() + 120
while time.time() < deadline:
    sched.check_expired_executors()
    if "zombie" not in sched.executor_manager.tracked_executors():
        break
    time.sleep(0.2)
while time.time() < deadline and sched.jobs[job_id].status not in (
    "completed", "failed"
):
    time.sleep(0.2)

assert "zombie" not in sched.executor_manager.tracked_executors()
assert sched.jobs[job_id].status == "completed", (
    sched.jobs[job_id].status, sched.jobs[job_id].error
)

# the job's results are intact: fetch the completed partitions directly
from ballista_tpu.executor.reader import fetch_partition_table
tables = [fetch_partition_table(loc)
          for loc in sched.jobs[job_id].completed_locations]
res = pa.concat_tables([t for t in tables if t.num_rows]).to_pandas()
df = t.to_pandas()
want = (df.groupby("k").agg(sv=("v", "sum"), n=("v", "count"))
        .reset_index())
res = res.sort_values("k").reset_index(drop=True)
want = want.sort_values("k").reset_index(drop=True)
np.testing.assert_array_equal(res.k, want.k)
np.testing.assert_array_equal(res.n, want.n)
np.testing.assert_allclose(res.sv, want.sv, rtol=1e-9)

loop2.stop()
ctx.close()
print("RECOVERY-OK", stuck)
"""


def test_dead_executor_task_reset():
    proc = subprocess.run(
        [sys.executable, "-c", SCRIPT],
        env=CPU_MESH_ENV,
        capture_output=True,
        text=True,
        timeout=420,
    )
    assert proc.returncode == 0, (
        f"stdout:\n{proc.stdout}\nstderr:\n{proc.stderr}"
    )
    assert "RECOVERY-OK" in proc.stdout
