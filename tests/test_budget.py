"""THE suppression-budget test (analysis/budget.py).

planlint/racelint/lifelint each used to carry their own ``<= 5``
assertion in their own file — three places a budget could silently grow.
This single test walks the shared ledger instead: every AST analyzer is
registered, every budget is enforced here and nowhere else, and growing
any budget means editing analysis/budget.py in plain sight of this
file."""

from ballista_tpu.analysis import budget


def test_every_analyzer_within_budget():
    ledger = budget.ledger()
    assert set(ledger) == {
        "jaxlint", "racelint", "lifelint", "eqlint", "detlint",
        "stalelint", "durlint",
    }
    for name, row in ledger.items():
        assert row["used"] <= row["budget"], (
            f"{name}: {row['used']} suppressions > budget {row['budget']}"
        )


def test_current_counts_pinned():
    """The live counts, pinned: a NEW suppression anywhere shows up as a
    diff to this test plus its in-code justification comment."""
    used = {k: v["used"] for k, v in budget.ledger().items()}
    assert used == {
        "jaxlint": 0,
        # the documented double-checked fast path in testing/faults.py
        "racelint": 1,
        "lifelint": 0,
        "eqlint": 0,
        "detlint": 0,
        "stalelint": 0,
        "durlint": 0,
    }, used


def test_budgets_are_uniform_and_small():
    assert set(budget.BUDGETS.values()) == {5}


def test_check_message_names_the_ledger():
    assert budget.check("eqlint", 5) is None
    msg = budget.check("eqlint", 6)
    assert msg is not None and "analysis/budget.py" in msg
