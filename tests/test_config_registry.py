"""Config-key & env-var registry closure (ISSUE 8 satellite).

Every ``ballista.*`` config-key literal and ``BALLISTA_*`` env read in
the package must resolve to a declared registry entry; docs/config.md is
generated from the registries and pinned here; the runtime
``warn_unknown_env`` catches the typo'd-knob case static analysis can't.
"""

import logging

from ballista_tpu import config as cfg
from ballista_tpu.analysis import configlint


def _rules(src: str):
    return [d.rule for d in configlint.lint_source(src)]


# ------------------------------------------------------------ tier-1 gate --


def test_tree_is_closed_over_the_registries():
    diags, summary = configlint.lint_tree()
    assert diags == [], "\n".join(str(d) for d in diags)
    # the scan saw real traffic (not vacuously green)
    import re

    m = re.match(r"(\d+) config-key literals \+ (\d+) env read", summary)
    assert m and int(m.group(1)) > 0 and int(m.group(2)) > 0, summary


def test_docs_config_md_is_pinned_to_the_registries():
    assert configlint.docs_path().exists(), (
        "docs/config.md missing — regenerate with "
        "`python -m ballista_tpu.analysis --write-config-docs`"
    )
    assert configlint.docs_path().read_text() == (
        configlint.render_config_docs()
    ), (
        "docs/config.md is stale vs config.py registries — regenerate "
        "with `python -m ballista_tpu.analysis --write-config-docs`"
    )


def test_generated_docs_cover_both_registries():
    text = configlint.render_config_docs()
    for name in cfg._entries():
        assert f"`{name}`" in text, name
    for e in cfg.ENV_REGISTRY:
        assert f"`{e.name}`" in text, e.name


# ----------------------------------------------------------- mutations --


def test_unknown_env_read_rejected_and_declared_accepted():
    assert _rules(
        'import os\nx = os.environ.get("BALLISTA_BOGUS_KNOB")\n'
    ) == ["unknown-env"]
    assert _rules(
        'import os\nx = os.environ.get("BALLISTA_TPU_PREWARM", "off")\n'
    ) == []
    # subscript + pop forms are covered too
    assert _rules(
        'import os\nx = os.environ["BALLISTA_NOPE"]\n'
    ) == ["unknown-env"]
    assert _rules(
        'import os\nos.environ.pop("BALLISTA_NOPE2", None)\n'
    ) == ["unknown-env"]


def test_fstring_env_reads_need_a_declared_prefix_family():
    assert _rules(
        "import os\n"
        "def f(name):\n"
        '    return os.environ.get(f"BALLISTA_SCHEDULER_{name}")\n'
    ) == []
    assert _rules(
        "import os\n"
        "def f(name):\n"
        '    return os.environ.get(f"BALLISTA_MYSTERY_{name}")\n'
    ) == ["unknown-env"]


def test_unknown_config_key_literal_rejected():
    assert _rules('k = "ballista.tpu.not_a_key"\n') == [
        "unknown-config-key"
    ]
    assert _rules('k = "ballista.tpu.prewarm"\n') == []
    # internal task props are declared by prefix
    assert _rules('k = "ballista.internal.task_attempt"\n') == []


# ------------------------------------------------------------- runtime --


def test_env_entry_for_exact_and_prefix():
    assert cfg.env_entry_for("BALLISTA_TPU_PREWARM").name == (
        "BALLISTA_TPU_PREWARM"
    )
    assert cfg.env_entry_for("BALLISTA_SCHEDULER_BIND_PORT").name == (
        "BALLISTA_SCHEDULER_*"
    )
    assert cfg.env_entry_for("BALLISTA_TYPO") is None


def test_warn_unknown_env_flags_typod_knobs(monkeypatch, caplog):
    monkeypatch.setenv("BALLISTA_PREWRAM", "on")  # the classic typo
    monkeypatch.setattr(cfg, "_ENV_WARNED", False)
    with caplog.at_level(logging.WARNING, logger="ballista_tpu.config"):
        unknown = cfg.warn_unknown_env()
    assert "BALLISTA_PREWRAM" in unknown
    assert any("BALLISTA_PREWRAM" in r.message for r in caplog.records)


def test_warn_unknown_env_clean_when_all_declared(monkeypatch):
    monkeypatch.delenv("BALLISTA_PREWRAM", raising=False)
    monkeypatch.setattr(cfg, "_ENV_WARNED", False)
    unknown = cfg.warn_unknown_env()
    assert unknown == [], unknown
