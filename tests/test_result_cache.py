"""Plan-fingerprint result cache (scheduler/result_cache.py,
docs/serving.md).

Unit coverage of the keying rules (uncacheable submissions return a
None key) and the bytes-bounded LRU (deterministic eviction order,
oversize rejection counted, disabled cache no-ops), plus standalone-
cluster acceptance: a repeated identical query is served from the
scheduler's cache without executor involvement, bit-exactly;
re-registration (the engine's append) invalidates by key; a scheduler
restart starts with an empty cache and never serves a recovered job's
payload.
"""

import time

import pyarrow as pa
import pytest

from ballista_tpu.config import BallistaConfig
from ballista_tpu.scheduler.result_cache import (
    ResultCache,
    ipc_to_table,
    result_cache_key,
    table_to_ipc,
)

# ---------------------------------------------------------------------------
# unit: LRU mechanics
# ---------------------------------------------------------------------------


def test_lru_eviction_order_is_deterministic():
    """Eviction pops strictly least-recently-used: insertion order,
    reordered only by get()'s recency touch — no hash-seed iteration
    anywhere (detlint discipline for the eviction path)."""
    c = ResultCache(capacity_bytes=100)
    # entry cap is capacity//4 = 25 bytes; use 20-byte payloads
    p = b"x" * 20
    for k in ("a", "b", "c", "d", "e"):
        assert c.put((k,), p)
    # 5*20=100 fits exactly; touching "a" then adding "f" must evict "b"
    assert c.get(("a",)) is not None
    assert c.put(("f",), p)
    assert c.get(("b",)) is None  # evicted (LRU after the "a" touch)
    assert c.get(("a",)) is not None  # survived: recency respected
    s = c.stats()
    assert s["evictions"] == 1
    assert s["entries"] == 5
    assert s["bytes"] == 100


def test_oversize_rejected_and_counted():
    c = ResultCache(capacity_bytes=100)
    assert not c.put(("big",), b"y" * 26)  # > capacity//4
    assert c.stats()["rejected_oversize"] == 1
    assert c.stats()["entries"] == 0


def test_disabled_cache_noops():
    c = ResultCache(capacity_bytes=0)
    assert not c.enabled
    assert not c.put(("k",), b"v")
    assert c.get(("k",)) is None
    assert c.stats()["hits"] == 0 and c.stats()["misses"] == 0


def test_none_key_counts_as_miss():
    """Uncacheable submissions (None keys) are counted misses so the
    reported hit ratio stays honest about them."""
    c = ResultCache(capacity_bytes=100)
    assert c.get(None) is None
    assert c.stats()["misses"] == 1


def test_put_replaces_and_rebalances_bytes():
    c = ResultCache(capacity_bytes=100)
    c.put(("k",), b"x" * 10)
    c.put(("k",), b"y" * 20)
    s = c.stats()
    assert s["entries"] == 1 and s["bytes"] == 20
    payload, _meta = c.get(("k",))
    assert payload == b"y" * 20


def test_ipc_roundtrip():
    t = pa.table({"a": [1, 2, 3], "b": ["x", "y", "z"]})
    assert ipc_to_table(table_to_ipc(t)).equals(t)


# ---------------------------------------------------------------------------
# unit: keying rules
# ---------------------------------------------------------------------------


def _local_ctx():
    from ballista_tpu.exec.context import TpuContext

    ctx = TpuContext()
    ctx.register_table("t", pa.table({"a": [1, 2, 3]}))
    return ctx


def test_key_is_stable_and_version_sensitive():
    from ballista_tpu.plan.optimizer import optimize

    ctx = _local_ctx()
    cfg = BallistaConfig()
    plan = optimize(ctx.sql_to_logical("select a from t where a > 1"))
    k1 = result_cache_key(plan, cfg, ctx)
    k2 = result_cache_key(plan, cfg, ctx)
    assert k1 is not None and k1 == k2
    # settings are part of the identity: sessions never collide
    k3 = result_cache_key(
        plan, cfg.with_setting("ballista.shuffle.partitions", "7"), ctx
    )
    assert k3 != k1
    # re-registration (the engine's append) changes _data_version
    ctx.register_table("t", pa.table({"a": [1, 2, 3, 4]}))
    assert result_cache_key(plan, cfg, ctx) != k1


def test_key_none_for_system_scans_and_missing_provider():
    from ballista_tpu.plan.optimizer import optimize

    ctx = _local_ctx()
    cfg = BallistaConfig()
    sys_plan = optimize(
        ctx.sql_to_logical("select * from system.queries")
    )
    assert result_cache_key(sys_plan, cfg, ctx) is None
    user_plan = optimize(ctx.sql_to_logical("select a from t"))

    class NoVersion:
        pass

    assert result_cache_key(user_plan, cfg, NoVersion()) is None


# ---------------------------------------------------------------------------
# acceptance: standalone cluster
# ---------------------------------------------------------------------------


def _standalone(data, **settings):
    from ballista_tpu.client.context import BallistaContext

    cfg = (
        BallistaConfig()
        .with_setting("ballista.shuffle.partitions", "2")
        .with_setting("ballista.tpu.result_cache_mb", "16")
    )
    for k, v in settings.items():
        cfg = cfg.with_setting(k.replace("__", "."), v)
    ctx = BallistaContext.standalone(cfg)
    for name, t in data.items():
        ctx.register_table(name, t)
    return ctx


def _wait_entries(sched, n, timeout=10.0):
    """Cache population is asynchronous (a background re-read of the
    committed partitions after JobFinished) — wait for it."""
    deadline = time.time() + timeout
    while time.time() < deadline:
        if sched.result_cache.stats()["entries"] >= n:
            return
        time.sleep(0.02)
    raise AssertionError(
        f"cache never reached {n} entries: {sched.result_cache.stats()}"
    )


def test_cache_hit_serves_without_executor_bit_exact():
    t = pa.table(
        {"k": [i % 5 for i in range(1000)],
         "v": [float(i) for i in range(1000)]}
    )
    ctx = _standalone({"t": t})
    sched = ctx._standalone_cluster.scheduler
    sql = "select k, sum(v) as s from t group by k order by k"
    try:
        cold = ctx.sql(sql).collect()
        _wait_entries(sched, 1)
        with sched._lock:
            jobs_before = len(sched.jobs)
        stages_before = sched.stage_manager.inflight_tasks()
        hit = ctx.sql(sql).collect()
        assert hit.equals(cold), "cache hit must be bit-exact"
        s = sched.result_cache.stats()
        assert s["hits"] >= 1, s
        # the hit minted a job (observability parity) but scheduled
        # nothing: no stages, no tasks, payload inline on the status
        with sched._lock:
            hit_job = max(
                sched.jobs.values(), key=lambda j: j.submitted_s
            )
            assert len(sched.jobs) == jobs_before + 1
        assert hit_job.status == "completed"
        assert hit_job.result_ipc
        assert not hit_job.stages
        assert sched.stage_manager.inflight_tasks() == stages_before
        # the cache span marks the hit in the job's event record
        # (observability: a hit is visible, not silent)
        assert hit_job.query_class not in ("", None)
        # history parity: the hit job is in the persistent query log
        assert any(
            r["job_id"] == hit_job.job_id for r in sched.history.jobs()
        )
    finally:
        ctx.close()


def test_append_and_reregister_invalidate_by_key():
    t = pa.table({"a": [1, 2, 3], "b": [1.0, 2.0, 3.0]})
    ctx = _standalone({"t": t})
    sched = ctx._standalone_cluster.scheduler
    sql = "select sum(a) as s from t"
    try:
        r1 = ctx.sql(sql).collect()
        assert r1.column("s")[0].as_py() == 6
        _wait_entries(sched, 1)
        # append (re-register with extra rows): next submission MISSES
        # and returns the fresh result
        t2 = pa.concat_tables([t, pa.table({"a": [10], "b": [10.0]})])
        ctx.register_table("t", t2)
        r2 = ctx.sql(sql).collect()
        assert r2.column("s")[0].as_py() == 16
        s = sched.result_cache.stats()
        assert s["hits"] == 0 and s["misses"] >= 2, s
        # the old entry is dead BY KEY — re-registering the original
        # table object still misses (id() changed => version changed)
        ctx.register_table("t", pa.table(t.to_pydict()))
        r3 = ctx.sql(sql).collect()
        assert r3.column("s")[0].as_py() == 6
        assert sched.result_cache.stats()["hits"] == 0
    finally:
        ctx.close()


def test_system_tables_never_cached():
    t = pa.table({"a": [1, 2, 3]})
    ctx = _standalone({"t": t})
    sched = ctx._standalone_cluster.scheduler
    try:
        ctx.sql("select a from t").collect()
        _wait_entries(sched, 1)
        before = sched.result_cache.stats()["entries"]
        ctx.sql("select * from system.queries").collect()
        ctx.sql("select * from system.queries").collect()
        # system scans must neither hit nor store (they serve the rows
        # as of THIS query)
        assert sched.result_cache.stats()["entries"] == before
        assert sched.result_cache.stats()["hits"] == 0
    finally:
        ctx.close()


def test_scheduler_restart_drops_cache(tmp_path):
    """The cache is in-memory only: a recovered scheduler starts empty
    and a recovered completed job carries no inline payload (clients
    re-fetch the durable partitions instead of a stale cache blob)."""
    from ballista_tpu.scheduler.persistent_state import (
        PersistentSchedulerState,
    )
    from ballista_tpu.scheduler.server import JobInfo, SchedulerServer
    from ballista_tpu.scheduler.state_backend import SqliteBackend

    backend = SqliteBackend(str(tmp_path / "s.db"))
    st = PersistentSchedulerState(backend, "default", None)
    job = JobInfo(job_id="abc9999", session_id="s1", status="completed")
    st.save_job(job)
    st.save_session("s1", {})

    cfg = BallistaConfig().with_setting(
        "ballista.tpu.result_cache_mb", "16"
    )
    recovered = SchedulerServer(
        provider=None, state_backend=backend, config=cfg
    )
    try:
        assert recovered.result_cache.enabled
        assert recovered.result_cache.stats()["entries"] == 0
        stp = recovered.job_status_proto("abc9999")
        assert stp.WhichOneof("status") == "completed"
        assert stp.completed.result_ipc == b""
    finally:
        recovered.shutdown()
