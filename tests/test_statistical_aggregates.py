"""STDDEV / STDDEV_POP / VARIANCE / VAR_POP / CORR aggregates.

Decomposed into SUM/COUNT state slots over synthesized pre-projection
expressions (x^2, pairwise-null-masked products), so the partial/merge/
final machinery, the distributed tier, and the mesh tier all get them for
free. Oracle: pandas. CORR uses pairwise deletion (rows where either
argument is NULL are excluded entirely), matching SQL.
"""

import subprocess
import sys

from tests.conftest import CPU_MESH_ENV

SCRIPT = r"""
import numpy as np
import pandas as pd
import pyarrow as pa

from ballista_tpu.exec.context import TpuContext

r = np.random.default_rng(5)
n = 4000
x = r.uniform(0, 100, n)
y = 0.4 * x + r.uniform(0, 30, n)
g = r.integers(0, 7, n).astype(np.int64)
# inject nulls into y (pairwise deletion must drop those rows for corr)
ymask = r.uniform(0, 1, n) < 0.1
t = pa.table({
    "g": pa.array(g),
    "x": pa.array(x),
    "y": pa.array(np.where(ymask, np.nan, y), mask=ymask),
})
ctx = TpuContext()
ctx.register_table("t", t)
df = t.to_pandas()

res = ctx.sql(
    "select g, stddev(x) sd, stddev_pop(x) sdp, variance(x) va, "
    "var_pop(x) vp, corr(x, y) c from t group by g order by g"
).collect().to_pandas()

want = df.groupby("g").agg(
    sd=("x", "std"),
    sdp=("x", lambda s: s.std(ddof=0)),
    va=("x", "var"),
    vp=("x", lambda s: s.var(ddof=0)),
).reset_index()
want["c"] = df.groupby("g").apply(
    lambda d: d.x.corr(d.y), include_groups=False
).values
np.testing.assert_allclose(res.sd, want.sd, rtol=1e-9)
np.testing.assert_allclose(res.sdp, want.sdp, rtol=1e-9)
np.testing.assert_allclose(res.va, want.va, rtol=1e-9)
np.testing.assert_allclose(res.vp, want.vp, rtol=1e-9)
np.testing.assert_allclose(res.c, want.c, rtol=1e-6)

# scalar (no GROUP BY) form + aliases
res2 = ctx.sql(
    "select stddev_samp(x) a, var_samp(x) b, corr(x, y) c from t"
).collect().to_pandas()
np.testing.assert_allclose(res2.a[0], df.x.std(), rtol=1e-9)
np.testing.assert_allclose(res2.b[0], df.x.var(), rtol=1e-9)
np.testing.assert_allclose(res2.c[0], df.x.corr(df.y), rtol=1e-6)

# var of a single row is NULL (sample), 0 for population
one = pa.table({"x": pa.array([5.0])})
ctx.register_table("one", one)
r3 = ctx.sql("select variance(x) v, var_pop(x) p from one").collect().to_pandas()
assert pd.isna(r3.v[0]) and r3.p[0] == 0.0, r3

# distributed parity
from ballista_tpu.client.context import BallistaContext
cctx = BallistaContext.standalone()
cctx.register_table("t", t)
res4 = cctx.sql(
    "select g, stddev(x) sd, corr(x, y) c from t group by g order by g"
).collect().to_pandas()
np.testing.assert_allclose(res4.sd, want.sd, rtol=1e-9)
np.testing.assert_allclose(res4.c, want.c, rtol=1e-6)
cctx.close()
print("STAT-AGGS-OK")
"""


def test_statistical_aggregates():
    env = {k: v for k, v in CPU_MESH_ENV.items() if k != "XLA_FLAGS"}
    proc = subprocess.run(
        [sys.executable, "-c", SCRIPT],
        env=env,
        capture_output=True,
        text=True,
        timeout=600,
    )
    assert proc.returncode == 0, (
        f"stdout:\n{proc.stdout}\nstderr:\n{proc.stderr[-3000:]}"
    )
    assert "STAT-AGGS-OK" in proc.stdout
