"""UDF plugin: drop a .py file in a plugin dir, use it from SQL.

The TPU-native analogue of the reference's plugin manager
(ref core/src/plugin/mod.rs:36-127, which dlopens .so files): plugins are
Python modules exposing ``register(register_udf)``; UDF bodies are
jax-traceable, so they fuse into the same XLA programs as built-ins.

Run:  python examples/udf_plugin.py
"""

import os
import tempfile
import textwrap

import numpy as np
import pyarrow as pa

from ballista_tpu.config import BallistaConfig
from ballista_tpu.exec.context import TpuContext


def main() -> None:
    plugin_dir = tempfile.mkdtemp(prefix="ballista-plugins-")
    with open(os.path.join(plugin_dir, "my_math.py"), "w") as f:
        f.write(
            textwrap.dedent(
                """
                import jax.numpy as jnp
                from ballista_tpu.datatypes import DataType

                def register(register_udf):
                    register_udf(
                        "relu", lambda x: jnp.maximum(x, 0.0),
                        DataType.FLOAT64,
                    )
                    register_udf(
                        "squared_distance", lambda a, b: (a - b) * (a - b),
                        DataType.FLOAT64, min_args=2, max_args=2,
                    )
                """
            )
        )

    ctx = TpuContext(
        BallistaConfig().with_setting("ballista.plugin_dir", plugin_dir)
    )
    rng = np.random.default_rng(3)
    ctx.register_table(
        "points",
        pa.table(
            {
                "x": pa.array(rng.normal(0, 2, 1000)),
                "y": pa.array(rng.normal(1, 2, 1000)),
            }
        ),
    )
    ctx.sql(
        "SELECT COUNT(*) AS n, AVG(relu(x)) AS avg_relu_x, "
        "AVG(squared_distance(x, y)) AS mean_sq_dist FROM points"
    ).show()


if __name__ == "__main__":
    main()
