"""DataFrame-builder API: compose queries without SQL.

The TPU-native analogue of the reference's DataFrame usage
(BallistaContext::read_csv().filter().aggregate() chains,
ref python/src/dataframe.rs:55-137): the same logical plans the SQL front
end produces, built programmatically.

Run:  python examples/dataframe.py
"""

import numpy as np
import pyarrow as pa

from ballista_tpu import functions as F
from ballista_tpu.exec.context import TpuContext
from ballista_tpu.expr.logical import col, lit


def main() -> None:
    ctx = TpuContext()
    rng = np.random.default_rng(2)
    n = 10_000
    ctx.register_table(
        "trips",
        pa.table(
            {
                "vendor": pa.array(rng.integers(1, 4, n)),
                "passengers": pa.array(rng.integers(1, 6, n)),
                "fare": pa.array(np.round(rng.uniform(3, 80, n), 2)),
            }
        ),
    )

    df = (
        ctx.table("trips")
        .filter(col("passengers") > lit(1))
        .aggregate(
            [col("vendor")],
            [
                F.count_star().alias("trips"),
                F.sum("fare").alias("revenue"),
                F.avg("fare").alias("avg_fare"),
            ],
        )
        .sort(col("vendor"))
    )
    df.show()


if __name__ == "__main__":
    main()
