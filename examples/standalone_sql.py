"""Standalone-cluster SQL: scheduler + executor in one process.

The TPU-native analogue of the reference's examples/standalone-sql.rs —
boot an in-proc cluster (real gRPC control plane + Flight data plane),
register a CSV, run SQL, print the result.

Run:  python examples/standalone_sql.py
"""

import csv
import os
import random
import tempfile

from ballista_tpu.client.context import BallistaContext
from ballista_tpu.config import BallistaConfig


def main() -> None:
    # a small CSV on disk, like the reference's testdata file
    tmp = tempfile.mkdtemp(prefix="ballista-example-")
    path = os.path.join(tmp, "sales.csv")
    rng = random.Random(0)
    with open(path, "w", newline="") as f:
        w = csv.writer(f)
        w.writerow(["region", "amount"])
        for _ in range(100):
            w.writerow(
                [rng.choice(["east", "west", "north"]),
                 round(rng.uniform(1, 100), 2)]
            )

    config = (
        BallistaConfig.builder()
        .with_setting("ballista.shuffle.partitions", "1")
    )
    ctx = BallistaContext.standalone(config=config)
    ctx.sql(
        f"CREATE EXTERNAL TABLE test STORED AS CSV "
        f"WITH HEADER ROW LOCATION '{path}'"
    ).collect()

    df = ctx.sql(
        "SELECT region, COUNT(1) AS n, SUM(amount) AS total "
        "FROM test GROUP BY region ORDER BY region"
    )
    df.show()
    ctx.close()


if __name__ == "__main__":
    main()
