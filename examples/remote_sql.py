"""Remote-cluster SQL: connect a client to a running scheduler.

The TPU-native analogue of the reference's remote flow
(docs/source/user-guide/distributed): start a scheduler + executor (here
in-process for a self-contained example; in production use
``python -m ballista_tpu.scheduler`` and ``python -m ballista_tpu.executor``
on separate hosts), then connect by address, register a file-backed table,
and run SQL over gRPC with results fetched over Arrow Flight.

Run:  python examples/remote_sql.py
"""

import csv
import os
import random
import tempfile

from ballista_tpu.client.context import BallistaContext
from ballista_tpu.standalone import StandaloneCluster


def main() -> None:
    # stand in for `python -m ballista_tpu.scheduler` + executor processes
    cluster = StandaloneCluster.start()

    # a CSV both "hosts" can see (shared storage in a real deployment)
    tmp = tempfile.mkdtemp(prefix="ballista-example-")
    path = os.path.join(tmp, "orders.csv")
    rng = random.Random(1)
    with open(path, "w", newline="") as f:
        w = csv.writer(f)
        w.writerow(["customer", "total"])
        for _ in range(1000):
            w.writerow([rng.randrange(50), round(rng.uniform(5, 500), 2)])

    # the remote client: exactly what you'd run on another machine
    ctx = BallistaContext.remote("localhost", cluster.scheduler_port)
    ctx.sql(
        f"CREATE EXTERNAL TABLE orders STORED AS CSV "
        f"WITH HEADER ROW LOCATION '{path}'"
    )

    df = ctx.sql(
        "SELECT customer, COUNT(*) AS n, SUM(total) AS spent "
        "FROM orders GROUP BY customer ORDER BY spent DESC LIMIT 5"
    )
    df.show()
    cluster.stop()


if __name__ == "__main__":
    main()
